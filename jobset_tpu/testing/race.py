"""RaceHarness: an opt-in Eraser-style dynamic lockset checker.

The static RACE rules (docs/static-analysis.md) reason about code; this
module watches an actual run. Inside a ``with RaceHarness() as rh:``
block:

* ``threading.Lock`` / ``RLock`` / ``Condition`` construction is
  patched to return *tracked* wrappers, so every lock created under the
  harness reports acquire/release into a per-thread held-lockset
  (``threading.Event`` rides along for free — it is built on
  ``Condition``).
* ``threading.Thread.start``/``join`` and ``Condition.wait``/
  ``notify``/``notify_all`` produce **happens-before** edges (vector
  clocks): state handed to a thread before ``start()``, or read back
  after ``join()``, or passed through a notify→wait pair, is ordered —
  not racy.
* Registered *hot classes* get instrumented attribute access: each
  watched ``(object, attr)`` runs the Eraser state machine
  (virgin → exclusive → shared → shared-modified) with HB-based
  ownership transfer, maintaining a **candidate lockset** — the
  intersection of the tracked locks held at every access. A write to
  shared state whose lockset goes empty is a race; the harness records
  it with BOTH access stacks.

Default watch set: the tree's hot threaded classes — ``Counter``,
``Gauge``, ``Histogram`` (core/metrics), ``FlowController`` (flow),
``FaultInjector`` (chaos), ``Store`` (store), ``ReplicationCoordinator``
/ ``FollowerLog`` (ha), ``ControllerServer`` (server), ``ShardRouter``
(shard — the merged-journal state the front door's handler threads and
the watch pollers share). Instances are
tracked when constructed **inside** the harness (construct the system
under test within the ``with`` block); pre-existing instances can be
``adopt()``-ed, which also swaps their untracked lock attributes for
tracked wrappers (only safe before their threads start).

Known limitations (docs/static-analysis.md has the full list):

* Watched attributes holding mutable containers are treated
  conservatively as written on every access — a lock-free *read* of a
  dict another thread mutates is exactly the ``Counter.value()`` bug,
  and Python's attribute hooks cannot see the subsequent ``[]``/
  ``.append``. Consequence: container attrs that are immutable after
  publication should simply not be watched.
* Locks created before the harness (module-level registries) are
  invisible unless their owner is ``adopt()``-ed; accesses under them
  look lock-free, so only adopted/in-harness objects are checked at
  all (no false positives, but no coverage either).
* Lockset emptiness is evidence, not proof: ad-hoc synchronization the
  harness cannot see (queue hand-offs between unwatched objects)
  surfaces as a false positive — fix by modeling the hand-off with a
  real join/Condition, or unwatch the attr with ``ignore``.

Wired into pytest as the ``race`` marker + ``race_harness`` fixture
(tests/conftest.py); the chaos soaks re-run under it in
tests/test_race_harness.py.
"""

from __future__ import annotations

import sys
import threading
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

__all__ = [
    "RaceHarness",
    "RaceError",
    "RaceReport",
    "default_watchlist",
]

# Real constructors, captured at import (the harness patches the
# threading module attributes, never these).
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition
_LOCK_TYPES = (type(_REAL_LOCK()), type(_REAL_RLOCK()))


def swap_lock_attrs(obj, wrap_lock, wrap_condition=None) -> list[tuple]:
    """Swap an instance's bare Lock/RLock (and, when ``wrap_condition``
    is given, Condition) attributes in place for wrappers built by the
    callbacks ``wrap(name, value)`` — the one lock-interposition seam
    shared by :meth:`RaceHarness.adopt` and the contention profiler
    (``obs/contention.py``). Returns ``(attr_name, original)`` pairs so
    callers can restore. Only safe before the object's threads are
    running: a lock swapped while held by another thread loses mutual
    exclusion with the holder."""
    swapped: list[tuple] = []
    for name, value in list(vars(obj).items()):
        if isinstance(value, _LOCK_TYPES):
            object.__setattr__(obj, name, wrap_lock(name, value))
            swapped.append((name, value))
        elif wrap_condition is not None and isinstance(
                value, _REAL_CONDITION):
            object.__setattr__(obj, name, wrap_condition(name, value))
            swapped.append((name, value))
    return swapped


@dataclass
class _Frame:
    filename: str
    lineno: int
    function: str

    def render(self) -> str:
        return f"{self.filename}:{self.lineno} in {self.function}"


def _capture_stack(limit: int = 10) -> tuple[_Frame, ...]:
    """Cheap stack capture (no line text), skipping harness frames."""
    frames: list[_Frame] = []
    f = sys._getframe(2)
    while f is not None and len(frames) < limit:
        filename = f.f_code.co_filename
        if "jobset_tpu/testing/race" not in filename.replace("\\", "/"):
            frames.append(_Frame(
                filename=filename, lineno=f.f_lineno,
                function=f.f_code.co_name,
            ))
        f = f.f_back
    return tuple(frames)


@dataclass
class _AccessInfo:
    thread: int
    thread_name: str
    write: bool
    locks: tuple[str, ...]
    lock_ids: frozenset
    stack: tuple[_Frame, ...]


@dataclass
class RaceReport:
    """One detected race on one (object, attribute)."""

    cls: str
    attr: str
    first: _AccessInfo
    second: _AccessInfo

    def render(self) -> str:
        def side(tag: str, info: _AccessInfo) -> str:
            locks = ", ".join(info.locks) or "<none>"
            stack = "\n    ".join(fr.render() for fr in info.stack[:6])
            kind = "write" if info.write else "read"
            return (
                f"  {tag}: {kind} on thread {info.thread_name} "
                f"holding [{locks}]\n    {stack}"
            )

        return (
            f"RACE on {self.cls}.{self.attr}: candidate lockset is "
            "empty (no common lock orders these accesses)\n"
            + side("first ", self.first) + "\n"
            + side("second", self.second)
        )


class RaceError(AssertionError):
    """Raised at harness exit when races were detected."""

    def __init__(self, races: list[RaceReport]):
        self.races = races
        super().__init__(
            f"{len(races)} race(s) detected:\n\n"
            + "\n\n".join(r.render() for r in races)
        )


# -- vector clocks -----------------------------------------------------------


class _VectorClock(dict):
    def copy(self) -> "_VectorClock":
        return _VectorClock(self)

    def merge(self, other: dict) -> None:
        for k, v in other.items():
            if v > self.get(k, 0):
                self[k] = v

    def happens_before(self, other: dict) -> bool:
        return all(v <= other.get(k, 0) for k, v in self.items())


# -- Eraser state machine ----------------------------------------------------

_VIRGIN = 0
_EXCLUSIVE = 1
_SHARED = 2
_SHARED_MODIFIED = 3


@dataclass
class _AttrState:
    state: int = _VIRGIN
    owner: int = 0                      # exclusive-owner thread id
    lockset: Optional[frozenset] = None  # candidate lockset (None=universe)
    last: Optional[_AccessInfo] = None
    last_vc: Optional[_VectorClock] = None
    reported: bool = False


# -- tracked primitives ------------------------------------------------------


class _TrackedLock:
    """Wrapper over a real lock; reports acquire/release to the harness.
    Reentrant acquires of a tracked RLock keep a depth count so the held
    set drops the lock only at the outermost release.

    Each lock also carries a message clock: release publishes the
    holder's vector clock, acquire absorbs it — the release->acquire
    happens-before edge (the TSan refinement over pure Eraser). Without
    it, every flag-checked-under-lock hand-off (threading.Event's
    already-set fast path) is a false positive."""

    def __init__(self, harness: "RaceHarness", inner, name: str):
        self._harness = harness
        self._inner = inner
        self._name = name
        self._msg_vc = _VectorClock()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._harness._on_acquire(self)
        return got

    def release(self):
        self._harness._on_release(self)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def _at_fork_reinit(self):  # pragma: no cover - forking servers only
        self._inner._at_fork_reinit()


class _TrackedCondition:
    """Condition wrapper: lock tracking + notify/wait HB edges."""

    def __init__(self, harness: "RaceHarness", lock=None, name: str = "",
                 _existing=None):
        self._harness = harness
        self._name = name or f"cond-{id(self):x}"
        if _existing is not None:
            # adopt(): wrap a live Condition in place — keep ITS lock
            # and waiter state, only interpose the tracking.
            self._inner = _existing
            self._lock = _TrackedLock(harness, _existing._lock, self._name)
        elif lock is None:
            self._lock = _TrackedLock(harness, _REAL_RLOCK(), self._name)
            self._inner = _REAL_CONDITION(self._lock._inner)
        elif isinstance(lock, _TrackedLock):
            self._lock = lock
            self._inner = _REAL_CONDITION(self._lock._inner)
        else:
            self._lock = _TrackedLock(harness, lock, self._name)
            self._inner = _REAL_CONDITION(self._lock._inner)
        # Message clock: joined VCs of every notifier so far.
        self._msg_vc = _VectorClock()

    # Lock surface (Condition is also a lock).
    def acquire(self, *args, **kwargs):
        return self._lock.acquire(*args, **kwargs)

    def release(self):
        self._lock.release()

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *exc):
        self._lock.release()
        return False

    def wait(self, timeout: Optional[float] = None):
        harness = self._harness
        # The real wait releases the underlying lock; mirror that in the
        # held set for the duration.
        harness._on_release(self._lock)
        try:
            got = self._inner.wait(timeout)
        finally:
            harness._on_acquire(self._lock)
            # HB edge: whatever every notifier did before notifying is
            # ordered before this wakeup (including timeout wakeups —
            # conservative: fewer false races, never more).
            harness._absorb_msg_vc(self._msg_vc)
        return got

    def wait_for(self, predicate, timeout: Optional[float] = None):
        # Re-implemented over wait() so the HB edges fire per wakeup.
        import time as _time

        end = None if timeout is None else _time.monotonic() + timeout
        result = predicate()
        while not result:
            remaining = None
            if end is not None:
                remaining = end - _time.monotonic()
                if remaining <= 0:
                    break
            self.wait(remaining)
            result = predicate()
        return result

    def notify(self, n: int = 1):
        self._harness._publish_msg_vc(self._msg_vc)
        self._inner.notify(n)

    def notify_all(self):
        self._harness._publish_msg_vc(self._msg_vc)
        self._inner.notify_all()

    notifyAll = notify_all


# -- per-thread state --------------------------------------------------------


class _ThreadState(threading.local):
    def __init__(self):
        self.held: list = []        # _TrackedLock stack (with depth dups)
        self.vc = _VectorClock()
        self.started = False


def default_watchlist() -> dict[type, frozenset]:
    """The hot classes the race plane watches by default, with the
    shared mutable attributes each one guards. Import failures (a
    fixture environment without the full tree) skip that class."""
    out: dict[type, frozenset] = {}

    def add(importer: Callable[[], type], attrs: Iterable[str]) -> None:
        try:
            cls = importer()
        except Exception:
            return
        out[cls] = frozenset(attrs)

    def _counter():
        from ..core.metrics import Counter

        return Counter

    def _gauge():
        from ..core.metrics import Gauge

        return Gauge

    def _histogram():
        from ..core.metrics import Histogram

        return Histogram

    add(_counter, ("_values",))
    add(_gauge, ("_values",))
    add(_histogram, ("counts", "sum", "n", "raw", "exemplars"))

    def _labeled_histogram():
        from ..core.metrics import LabeledHistogram

        return LabeledHistogram

    add(_labeled_histogram, ("_children",))

    def _stack_profiler():
        from ..obs.profile import StackProfiler

        return StackProfiler

    add(_stack_profiler, ("_root", "_node_count", "_dropped_frames",
                          "_samples", "_interval_counts",
                          "_interval_samples", "_ring"))

    def _flow():
        from ..flow.controller import FlowController

        return FlowController

    add(_flow, ("log", "_arrivals", "_rejected"))

    def _injector():
        from ..chaos.injector import FaultInjector

        return FaultInjector

    add(_injector, ("log", "_seq", "_arrivals", "_injected_by_point",
                    "_rules"))

    def _store():
        from ..store.store import Store

        return Store

    add(_store, ("seq", "commit_seq", "resource_version"))

    def _coordinator():
        from ..ha.replication import ReplicationCoordinator

        return ReplicationCoordinator

    add(_coordinator, ("_buffer", "_peer_acked", "_peer_next", "fenced",
                       "lost_quorum", "_quorum_failures"))

    def _follower():
        from ..ha.replication import FollowerLog

        return FollowerLog

    add(_follower, ("records", "last_seq", "commit_seq", "term",
                    "last_entry_term"))

    def _server():
        from ..server import ControllerServer

        return ControllerServer

    add(_server, ("_watch_events", "_watch_rv", "_watch_trimmed_rv",
                  "_quorum_rv", "_events_cursor"))

    def _shard_router():
        from ..shard.router import ShardRouter

        return ShardRouter

    add(_shard_router, ("_events", "_rv", "_trimmed_rv", "_cursors",
                        "_planned_homes"))

    def _tsdb():
        from ..obs.tsdb import TimeSeriesStore

        return TimeSeriesStore

    # The wall sampler thread appends while HTTP handlers query/snapshot.
    add(_tsdb, ("_series", "_first_ts"))

    def _alert_manager():
        from ..obs.alerts import AlertManager

        return AlertManager

    # evaluate() (sampler tick) vs state()/transition_log() (handlers).
    add(_alert_manager, ("_active", "_transitions"))

    def _migrations():
        from ..shard.migrate import MigrationController

        return MigrationController

    # step() (plane supervisor thread) vs note_plan()/describe()
    # (re-solve trigger + /debug/migrations handlers).
    add(_migrations, ("_desired", "_streak", "_active", "_history"))
    return out


class RaceHarness:
    """Context manager that watches a run for lockset violations.

    Parameters:

    * ``watch`` — ``{cls: iterable-of-attrs}`` to watch *instead of*
      the default hot-class list (pass ``extra`` to add to it).
    * ``extra`` — additional ``{cls: attrs}`` merged over the default.
    * ``ignore`` — ``{(ClsName, attr), ...}`` to silence.
    * ``raise_on_exit`` — raise :class:`RaceError` from ``__exit__``
      when races were found (default True; the pytest fixture relies
      on it).
    """

    def __init__(
        self,
        watch: Optional[dict] = None,
        extra: Optional[dict] = None,
        ignore: Optional[set] = None,
        raise_on_exit: bool = True,
    ):
        watched = (
            {k: frozenset(v) for k, v in watch.items()}
            if watch is not None else default_watchlist()
        )
        for cls, attrs in (extra or {}).items():
            watched[cls] = watched.get(cls, frozenset()) | frozenset(attrs)
        self._watched = watched
        self._ignore = set(ignore or ())
        self._raise = raise_on_exit
        self._internal = _REAL_LOCK()
        self._threads = _ThreadState()
        # id -> the object itself: tracked instances are PINNED for the
        # harness's lifetime so a recycled id() can never alias a dead
        # object's attribute states onto a new untracked one.
        self._tracked_objects: dict[int, object] = {}
        self._attr_states: dict[tuple[int, str], _AttrState] = {}
        self._races: list[RaceReport] = []
        self._patches: list[tuple] = []
        self._active = False
        self._thread_final_vc: dict[int, _VectorClock] = {}
        self._ident = threading.get_ident
        self._lock_seq = 0

    # -- lock bookkeeping --------------------------------------------------

    def _on_acquire(self, lock: _TrackedLock) -> None:
        self._threads.held.append(lock)
        self._absorb_msg_vc(lock._msg_vc)

    def _on_release(self, lock: _TrackedLock) -> None:
        held = self._threads.held
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                self._publish_msg_vc(lock._msg_vc)
                return

    def _held_names(self) -> tuple[str, ...]:
        seen = []
        for lock in self._threads.held:
            if lock._name not in seen:
                seen.append(lock._name)
        return tuple(seen)

    def _held_ids(self) -> frozenset:
        return frozenset(id(lock) for lock in self._threads.held)

    # -- vector clocks -----------------------------------------------------

    def _tick(self) -> None:
        me = self._ident()
        self._threads.vc[me] = self._threads.vc.get(me, 0) + 1

    def _publish_msg_vc(self, msg_vc: _VectorClock) -> None:
        with self._internal:
            msg_vc.merge(self._threads.vc)
        self._tick()

    def _absorb_msg_vc(self, msg_vc: _VectorClock) -> None:
        with self._internal:
            self._threads.vc.merge(msg_vc)

    # -- instrumentation ---------------------------------------------------

    def _instrument_class(self, cls: type, attrs: frozenset) -> None:
        orig_init = cls.__init__
        orig_setattr = cls.__setattr__
        orig_getattribute = cls.__getattribute__
        harness = self

        def init(obj, *args, **kwargs):
            with harness._internal:
                harness._tracked_objects[id(obj)] = obj
            orig_init(obj, *args, **kwargs)

        def setattr_(obj, name, value):
            if isinstance(value, _TrackedLock) and value._name.startswith(
                "anon-"
            ):
                value._name = f"{type(obj).__name__}.{name}"
            elif isinstance(value, _TrackedCondition) and (
                value._name.startswith("cond-")
            ):
                value._name = f"{type(obj).__name__}.{name}"
                value._lock._name = value._name
            if name in attrs and harness._is_tracked(obj):
                harness._on_access(obj, name, write=True)
            orig_setattr(obj, name, value)

        def getattribute(obj, name):
            value = orig_getattribute(obj, name)
            if name in attrs and harness._is_tracked(obj):
                # Mutable containers are conservatively writes (module
                # docstring); true scalars are reads.
                write = isinstance(value, (list, dict, set, bytearray))
                if type(value).__name__ == "deque":
                    write = True
                harness._on_access(obj, name, write=write)
            return value

        cls.__init__ = init
        cls.__setattr__ = setattr_
        cls.__getattribute__ = getattribute
        self._patches.append(
            (cls, orig_init, orig_setattr, orig_getattribute)
        )

    def _is_tracked(self, obj) -> bool:
        return (
            self._active
            and self._tracked_objects.get(id(obj)) is obj
        )

    def adopt(self, obj) -> None:
        """Track a pre-existing instance of a watched class: registers
        it and swaps its (untracked) lock attributes for tracked
        wrappers. Only safe before the object's threads are running —
        a lock swapped while held by another thread loses mutual
        exclusion with the holder."""
        cls = type(obj)
        if not any(
            cls is watched or issubclass(cls, watched)
            for watched in self._watched
        ):
            raise ValueError(
                f"{cls.__name__} is not a watched class; pass it via "
                "watch=/extra="
            )
        swap_lock_attrs(
            obj,
            lambda name, value: _TrackedLock(self, value, name),
            lambda name, value: _TrackedCondition(
                self, name=name, _existing=value
            ),
        )
        with self._internal:
            self._tracked_objects[id(obj)] = obj

    # -- the Eraser core ---------------------------------------------------

    def _on_access(self, obj, attr: str, write: bool) -> None:
        me = self._ident()
        key = (id(obj), attr)
        cls_name = type(obj).__name__
        if (cls_name, attr) in self._ignore:
            return
        # Every access ticks the thread's own clock so an exclusive
        # epoch is only transferable through a REAL happens-before edge
        # (start/join/notify), never through two empty clocks.
        self._threads.vc[me] = self._threads.vc.get(me, 0) + 1
        held = self._held_ids()
        info = _AccessInfo(
            thread=me,
            thread_name=threading.current_thread().name,
            write=write,
            locks=self._held_names(),
            lock_ids=held,
            stack=_capture_stack(),
        )
        with self._internal:
            st = self._attr_states.get(key)
            if st is None:
                st = self._attr_states[key] = _AttrState()
            my_vc = self._threads.vc
            if st.state == _VIRGIN:
                st.state = _EXCLUSIVE
                st.owner = me
                st.last = info
                st.last_vc = my_vc.copy()
                return
            if st.state == _EXCLUSIVE:
                if st.owner == me:
                    st.last = info
                    st.last_vc = my_vc.copy()
                    return
                if st.last_vc is not None and st.last_vc.happens_before(
                    my_vc
                ):
                    # Ownership transfer: every prior access is ordered
                    # before this one (start/join/notify chain).
                    st.owner = me
                    st.last = info
                    st.last_vc = my_vc.copy()
                    return
                # Genuinely concurrent second thread: demote. The
                # candidate lockset is the intersection of BOTH
                # accesses' held locks — seeding from only the second
                # access would let a one-shot unlocked write slip by
                # when every later access is consistently locked.
                st.lockset = (
                    held if st.last is None else (st.last.lock_ids & held)
                )
                prior_write = st.last.write if st.last else False
                st.state = (
                    _SHARED_MODIFIED if (write or prior_write) else _SHARED
                )
                self._maybe_report(st, cls_name, attr, info)
                st.last = info
                return
            # SHARED / SHARED_MODIFIED: refine the candidate lockset.
            st.lockset = (
                held if st.lockset is None else (st.lockset & held)
            )
            if write:
                st.state = _SHARED_MODIFIED
            self._maybe_report(st, cls_name, attr, info)
            st.last = info

    def _maybe_report(
        self, st: _AttrState, cls_name: str, attr: str, info: _AccessInfo
    ) -> None:
        if (
            st.state == _SHARED_MODIFIED
            and not st.lockset
            and not st.reported
            and st.last is not None
        ):
            st.reported = True
            self._races.append(RaceReport(
                cls=cls_name, attr=attr, first=st.last, second=info,
            ))

    # -- harness lifecycle -------------------------------------------------

    def __enter__(self) -> "RaceHarness":
        if self._active:
            raise RuntimeError("RaceHarness is not reentrant")
        harness = self

        def make_lock():
            harness._lock_seq += 1
            return _TrackedLock(
                harness, _REAL_LOCK(), f"anon-{harness._lock_seq}"
            )

        def make_rlock():
            harness._lock_seq += 1
            return _TrackedLock(
                harness, _REAL_RLOCK(), f"anon-{harness._lock_seq}"
            )

        def make_condition(lock=None):
            return _TrackedCondition(harness, lock)

        self._saved_threading = (
            threading.Lock, threading.RLock, threading.Condition,
            threading.Thread.start, threading.Thread.join,
        )
        threading.Lock = make_lock
        threading.RLock = make_rlock
        threading.Condition = make_condition

        real_start = threading.Thread.start
        real_join = threading.Thread.join

        def start(thread_self):
            # HB: child begins with everything the parent did so far.
            parent_vc = harness._threads.vc.copy()
            harness._tick()
            real_run = thread_self.run

            def run_with_vc(*args, **kwargs):
                harness._threads.vc.merge(parent_vc)
                try:
                    return real_run(*args, **kwargs)
                finally:
                    with harness._internal:
                        harness._thread_final_vc[thread_self.ident] = (
                            harness._threads.vc.copy()
                        )

            thread_self.run = run_with_vc
            return real_start(thread_self)

        def join(thread_self, timeout=None):
            result = real_join(thread_self, timeout)
            if not thread_self.is_alive():
                with harness._internal:
                    final = harness._thread_final_vc.get(
                        thread_self.ident
                    )
                if final is not None:
                    harness._threads.vc.merge(final)
            return result

        threading.Thread.start = start
        threading.Thread.join = join

        for cls, attrs in self._watched.items():
            self._instrument_class(cls, attrs)
        self._active = True
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self._active = False
        (threading.Lock, threading.RLock, threading.Condition,
         threading.Thread.start, threading.Thread.join) = (
            self._saved_threading
        )
        for cls, orig_init, orig_setattr, orig_getattribute in (
            self._patches
        ):
            cls.__init__ = orig_init
            cls.__setattr__ = orig_setattr
            cls.__getattribute__ = orig_getattribute
        self._patches.clear()
        if exc_type is None and self._raise and self._races:
            raise RaceError(self._races)
        return False

    # -- results -----------------------------------------------------------

    def races(self) -> list[RaceReport]:
        with self._internal:
            return list(self._races)

    def render(self) -> str:
        return "\n\n".join(r.render() for r in self.races())
