"""Builder-pattern test fixtures.

Mirrors the fixture style of the reference test suite
(`pkg/util/testing/wrappers.go:43-117`):
`make_jobset("js").replicated_job(make_replicated_job("rj").replicas(2).obj()).obj()`.
"""

from __future__ import annotations

from ..api import (
    Coordinator,
    FailurePolicy,
    JobSet,
    JobSetSpec,
    JobSpec,
    JobTemplateSpec,
    Network,
    ObjectMeta,
    PodSpec,
    PodTemplateSpec,
    ReplicatedJob,
    StartupPolicy,
    SuccessPolicy,
    keys,
)


def test_pod_spec() -> PodSpec:
    """Default pod spec used across tests (wrappers.go:27-35 analog)."""
    return PodSpec(restart_policy=keys.RESTART_POLICY_ON_FAILURE)


class ReplicatedJobWrapper:
    def __init__(self, name: str):
        self._rjob = ReplicatedJob(
            name=name,
            template=JobTemplateSpec(
                spec=JobSpec(template=PodTemplateSpec(spec=test_pod_spec()))
            ),
        )

    def replicas(self, n: int) -> "ReplicatedJobWrapper":
        self._rjob.replicas = n
        return self

    def parallelism(self, n: int) -> "ReplicatedJobWrapper":
        self._rjob.template.spec.parallelism = n
        return self

    def completions(self, n: int) -> "ReplicatedJobWrapper":
        self._rjob.template.spec.completions = n
        return self

    def completion_mode(self, mode: str) -> "ReplicatedJobWrapper":
        self._rjob.template.spec.completion_mode = mode
        return self

    def job_annotations(self, annotations: dict) -> "ReplicatedJobWrapper":
        self._rjob.template.annotations.update(annotations)
        return self

    def job_labels(self, labels: dict) -> "ReplicatedJobWrapper":
        self._rjob.template.labels.update(labels)
        return self

    def pod_annotations(self, annotations: dict) -> "ReplicatedJobWrapper":
        self._rjob.template.spec.template.annotations.update(annotations)
        return self

    def pod_labels(self, labels: dict) -> "ReplicatedJobWrapper":
        self._rjob.template.spec.template.labels.update(labels)
        return self

    def node_selector(self, selector: dict) -> "ReplicatedJobWrapper":
        self._rjob.template.spec.template.spec.node_selector.update(selector)
        return self

    def restart_policy(self, policy: str) -> "ReplicatedJobWrapper":
        self._rjob.template.spec.template.spec.restart_policy = policy
        return self

    def workload(self, payload: dict) -> "ReplicatedJobWrapper":
        self._rjob.template.spec.template.spec.workload = dict(payload)
        return self

    def obj(self) -> ReplicatedJob:
        return self._rjob


class JobSetWrapper:
    def __init__(self, name: str, namespace: str = "default"):
        self._js = JobSet(
            metadata=ObjectMeta(name=name, namespace=namespace),
            spec=JobSetSpec(),
        )

    def replicated_job(self, rjob: ReplicatedJob) -> "JobSetWrapper":
        self._js.spec.replicated_jobs.append(rjob)
        return self

    def suspend(self, suspended: bool) -> "JobSetWrapper":
        self._js.spec.suspend = suspended
        return self

    def success_policy(self, policy: SuccessPolicy) -> "JobSetWrapper":
        self._js.spec.success_policy = policy
        return self

    def failure_policy(self, policy: FailurePolicy) -> "JobSetWrapper":
        self._js.spec.failure_policy = policy
        return self

    def startup_policy(self, policy: StartupPolicy) -> "JobSetWrapper":
        self._js.spec.startup_policy = policy
        return self

    def network(self, network: Network) -> "JobSetWrapper":
        self._js.spec.network = network
        return self

    def network_subdomain(self, subdomain: str) -> "JobSetWrapper":
        if self._js.spec.network is None:
            self._js.spec.network = Network()
        self._js.spec.network.subdomain = subdomain
        return self

    def enable_dns_hostnames(self, enabled: bool) -> "JobSetWrapper":
        if self._js.spec.network is None:
            self._js.spec.network = Network()
        self._js.spec.network.enable_dns_hostnames = enabled
        return self

    def coordinator(self, coordinator: Coordinator) -> "JobSetWrapper":
        self._js.spec.coordinator = coordinator
        return self

    def managed_by(self, manager: str) -> "JobSetWrapper":
        self._js.spec.managed_by = manager
        return self

    def ttl_seconds_after_finished(self, ttl: int) -> "JobSetWrapper":
        self._js.spec.ttl_seconds_after_finished = ttl
        return self

    def annotations(self, annotations: dict) -> "JobSetWrapper":
        self._js.metadata.annotations.update(annotations)
        return self

    def exclusive_placement(self, topology_key: str) -> "JobSetWrapper":
        self._js.metadata.annotations[keys.EXCLUSIVE_KEY] = topology_key
        return self

    def node_selector_strategy(self, enabled: bool = True) -> "JobSetWrapper":
        if enabled:
            self._js.metadata.annotations[keys.NODE_SELECTOR_STRATEGY_KEY] = "true"
        return self

    def queue(self, queue_name: str, priority: int = 0) -> "JobSetWrapper":
        """Submit through an admission queue (queue/ subsystem)."""
        self._js.spec.queue_name = queue_name
        self._js.spec.priority = priority
        return self

    def obj(self) -> JobSet:
        return self._js


def make_jobset(name: str, namespace: str = "default") -> JobSetWrapper:
    return JobSetWrapper(name, namespace)


def make_replicated_job(name: str) -> ReplicatedJobWrapper:
    return ReplicatedJobWrapper(name)
