// Native data-loader hot path (the reference's runtime is compiled Go; the
// feed path here is the analog surface worth compiling — SURVEY.md §5's
// "IO" bullet). Python-side contract: jobset_tpu/utils/native.py builds
// this with g++ on first use and falls back to the numpy implementation
// when no toolchain is available, so the wheel needs no build step.
//
// gather_windows_u16_i32: one fused pass over a memory-mapped uint16 token
// stream producing the LM batch directly —
//   inputs[i, j]  = tokens[starts[i] + j]      (j < window)
//   targets[i, j] = tokens[starts[i] + j + 1]
// widened to int32, returning the max token id seen (the vocab-bounds
// check rides the same pass). Replaces four numpy passes (per-row window
// copies + stack, astype, and two ascontiguousarray slice copies).

#include <cstdint>

extern "C" {

int32_t gather_windows_u16_i32(const uint16_t* tokens,
                               const int64_t* starts,
                               int64_t n_rows,
                               int64_t window,
                               int32_t* inputs,
                               int32_t* targets) {
  int32_t max_id = -1;
  for (int64_t i = 0; i < n_rows; ++i) {
    const uint16_t* src = tokens + starts[i];
    int32_t* in_row = inputs + i * window;
    int32_t* tgt_row = targets + i * window;
    // First token only feeds inputs; the final (window-th) only targets.
    int32_t prev = static_cast<int32_t>(src[0]);
    if (prev > max_id) max_id = prev;
    for (int64_t j = 0; j < window; ++j) {
      const int32_t nxt = static_cast<int32_t>(src[j + 1]);
      if (nxt > max_id) max_id = nxt;
      in_row[j] = prev;
      tgt_row[j] = nxt;
      prev = nxt;
    }
  }
  return max_id;
}

}  // extern "C"
